"""Disaggregated prefill/decode pools (docs/OPERATIONS.md "Disaggregated
pools", docs/ARCHITECTURE.md "Two-phase dispatch").

Covers the contracts ISSUE 16 pins:
  - role advertisement: `AGENTFIELD_NODE_ROLE` / build_model_node(role=...)
    lands in registration metadata, invalid roles are rejected, and the
    registry sweep publishes the per-role `nodes_by_role` gauge;
  - a default all-`mixed` fleet is bit-compatible with pre-pools dispatch
    (no phase state, no handoff counters, pick order unchanged);
  - two-phase dispatch on a role-split fleet is token-exact under greedy
    (prefill-node single-node output == handed-off output), counted, and
    renders as ONE waterfall (`gateway.handoff` + `engine.kv_export`);
  - seeded kv.handoff_fail / kv.handoff_stall chaos degrades to
    single-node execution — token-exact, zero leaked pages on BOTH nodes;
  - handoff counters are always-present in stats → heartbeat → /metrics.
"""

import asyncio

import pytest

from agentfield_tpu.control_plane import faults
from agentfield_tpu.control_plane.types import (
    Execution,
    ExecutionStatus,
    TargetType,
)
from tests.helpers_cp import CPHarness, async_test

# Engine/model imports stay inside the tests that need a real model node,
# so the control-plane-only tests stay jax-light.

HANDOFF_COUNTERS = (
    "kv_handoff_initiated_total",
    "kv_handoff_completed_total",
    "kv_handoff_failed_total",
    "kv_handoff_bytes_total",
)


def test_handoff_fault_points_are_known():
    assert "kv.handoff_fail" in faults.KNOWN_POINTS
    assert "kv.handoff_stall" in faults.KNOWN_POINTS


# ---------------------------------------------------------------------------
# role-aware routing (control plane only; stub nodes)


def _exec_for(target: str, tokens=None, execution_id="exec_t"):
    inp = {"tokens": tokens, "max_new_tokens": 4} if tokens is not None else {"x": 1}
    return Execution(
        execution_id=execution_id,
        target=target,
        target_type=TargetType.REASONER,
        status=ExecutionStatus.RUNNING,
        run_id="run_t",
        input=inp,
    )


async def _role_cluster(h, roles):
    for i, role in enumerate(roles):
        md = {"model": "m", "channel": True}
        if role is not None:
            md["role"] = role
        await h.cp.registry.register(
            {
                "node_id": f"g{i}",
                "base_url": "http://127.0.0.1:9",
                "kind": "model",
                "reasoners": [{"id": "generate"}],
                "metadata": md,
            }
        )


@async_test
async def test_pick_node_role_routing_and_mixed_bit_compat():
    toks = list(range(40))
    async with CPHarness() as h:
        gw = h.cp.gateway
        # (1) role-less fleet (no `role` metadata at all): the pre-pools
        # pick order, bit-for-bit, and no phase state is ever created.
        await _role_cluster(h, [None, None, None])
        ex = _exec_for("g0.generate", toks)
        assert (await gw._pick_node(ex, set())).node_id == "g0"
        assert (await gw._pick_node(ex, {"g0"})).node_id in ("g1", "g2")
        assert gw._handoff == {}

    async with CPHarness() as h:
        gw = h.cp.gateway
        # (2) explicit all-mixed fleet: identical to (1)
        await _role_cluster(h, ["mixed", "mixed", "mixed"])
        ex = _exec_for("g0.generate", toks)
        assert (await gw._pick_node(ex, set())).node_id == "g0"
        assert gw._handoff == {}

    async with CPHarness() as h:
        gw = h.cp.gateway
        # (3) role-split fleet: eligible work goes to the prefill pool and
        # arms phase 1, even when the NAMED target is a decode node
        await _role_cluster(h, ["decode", "prefill", "decode"])
        ex = _exec_for("g0.generate", toks)
        picked = await gw._pick_node(ex, set())
        assert picked.node_id == "g1"
        assert gw._handoff["exec_t"] == {"phase": 1, "prefill_node": "g1"}
        gw._handoff.clear()

        # (4) ineligible work (text prompt) keeps OFF the prefill pool
        ex_text = Execution(
            execution_id="exec_text", target="g1.generate",
            target_type=TargetType.REASONER, status=ExecutionStatus.RUNNING,
            run_id="run_t",
            input={"prompt": "hello there", "max_new_tokens": 4},
        )
        assert (await gw._pick_node(ex_text, set())).node_id in ("g0", "g2")
        assert gw._handoff == {}

        # (5) phase 2 picks from the decode pool, never the prefill node,
        # and plants the whole-prompt transfer hint with the handoff id
        gw._handoff["exec_t"] = {
            "phase": 2, "prefill_node": "g1",
            "desc": {"id": "r1", "pages": 4, "page_size": 8},
            "t0w": 0.0, "t0m": 0.0,
        }
        picked = await gw._pick_node(ex, set())
        assert picked.node_id in ("g0", "g2")
        assert gw._kv_hints["exec_t"] == {
            "node_id": "g1", "pages": 4, "page_size": 8, "handoff": "r1",
        }
        gw._handoff.clear()
        gw._kv_hints.clear()

    async with CPHarness() as h:
        gw = h.cp.gateway
        # (6) prefill-only fleet (no decode, no mixed): nothing else can
        # serve — eligible work still dispatches (to the prefill node)
        # rather than stranding, and no phase state is armed
        await _role_cluster(h, ["prefill"])
        ex = _exec_for("g0.generate", toks)
        assert (await gw._pick_node(ex, set())).node_id == "g0"
        assert gw._handoff == {}

        # (7) empty decode pool at phase 2 degrades to the prefill node
        # and counts the fallback
        gw._handoff["exec_t"] = {
            "phase": 2, "prefill_node": "g0",
            "desc": {"id": "r2", "pages": 4, "page_size": 8},
            "t0w": 0.0, "t0m": 0.0,
        }
        picked = await gw._pick_node(ex, set())
        assert picked.node_id == "g0"
        assert gw._handoff == {}
        assert (
            h.cp.metrics.counter_value("gateway_handoff_fallback_total") == 1
        )


@async_test
async def test_handoff_transition_classification():
    async with CPHarness() as h:
        gw = h.cp.gateway
        await _role_cluster(h, ["prefill", "decode"])
        node = await gw._node_get("g0")
        ex = _exec_for("g0.generate", list(range(20)))

        # non-handoff result from phase 1 = the prefill node declined and
        # decoded itself: terminal as-is, state dropped, fallback counted
        gw._handoff["exec_t"] = {"phase": 1, "prefill_node": "g0"}
        assert gw._handoff_transition(ex, node, {"tokens": [1, 2]}) is False
        assert gw._handoff == {}
        assert h.cp.metrics.counter_value("gateway_handoff_fallback_total") == 1

        # handoff terminal WITHOUT a usable descriptor: re-dispatch plain
        # (True) — a 1-token phase-1 stub must never complete the execution
        gw._handoff["exec_t"] = {"phase": 1, "prefill_node": "g0"}
        stub = {"tokens": [7], "finish_reason": "handoff"}
        assert gw._handoff_transition(ex, node, stub) is True
        assert gw._handoff == {}

        # valid descriptor: phase-2 state armed
        gw._handoff["exec_t"] = {"phase": 1, "prefill_node": "g0"}
        ok = {
            "tokens": [7], "finish_reason": "handoff",
            "handoff": {"id": "r9", "t0": 7, "prompt_tokens": 20,
                        "pages": 2, "page_size": 8},
        }
        assert gw._handoff_transition(ex, node, ok) is True
        st = gw._handoff["exec_t"]
        assert st["phase"] == 2 and st["prefill_node"] == "g0"
        assert st["desc"]["id"] == "r9"


# ---------------------------------------------------------------------------
# real two-node fleets (model nodes; token-exact + chaos + zero-leak)


def _boot_pair():
    import jax

    from agentfield_tpu.models import get_config, init_params
    from agentfield_tpu.serving import EngineConfig

    cfg = get_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(max_batch=2, page_size=8, num_pages=64, max_pages_per_seq=16)
    return cfg, params, ecfg


async def _boot_roles(h, params, ecfg, roles):
    from agentfield_tpu.serving.model_node import build_model_node

    pairs = []
    for i, role in enumerate(roles):
        agent, back = build_model_node(
            f"node-{i}", h.base_url, model="llama-tiny", params=params,
            ecfg=ecfg, role=role,
        )
        await back.start()
        await agent.start()
        pairs.append((agent, back))
    return pairs


async def _stop_nodes(*pairs):
    for agent, back in pairs:
        await agent.stop()
        await back.stop()


async def _gen(h, target, body):
    async with h.http.post(f"/api/v1/execute/{target}", json={"input": body}) as r:
        doc = await r.json()
    assert doc["status"] == "completed", doc
    return doc


async def _assert_drained_zero_leak(*backs):
    for back in backs:
        for _ in range(100):
            if not back.engine.has_work():
                break
            await asyncio.sleep(0.05)
        assert not back.engine.has_work()
        pool = back.engine.allocator
        assert pool.free_pages == pool.num_pages - 1


def test_role_env_knob_and_validation(monkeypatch):
    from agentfield_tpu.serving.model_node import build_model_node

    _cfg, params, ecfg = _boot_pair()
    with pytest.raises(ValueError):
        build_model_node(
            "bad", "http://127.0.0.1:9", model="llama-tiny", params=params,
            ecfg=ecfg, role="turbo",
        )
    monkeypatch.setenv("AGENTFIELD_NODE_ROLE", "decode")
    agent, back = build_model_node(
        "envy", "http://127.0.0.1:9", model="llama-tiny", params=params, ecfg=ecfg
    )
    assert agent.metadata["role"] == "decode"
    # counters exist before any traffic: the always-present contract
    for k in HANDOFF_COUNTERS:
        assert back.engine.stats[k] == 0
    back.engine.close()


@async_test
async def test_two_phase_handoff_token_exact_counters_and_trace():
    """The pinned tentpole contract: a role-split fleet produces the exact
    greedy tokens single-node execution would, the handoff is counted on
    both sides, the per-role gauge publishes, and the request renders as
    ONE waterfall with gateway.handoff + engine.kv_export spans."""
    _cfg, params, ecfg = _boot_pair()
    async with CPHarness() as h:
        (p_agent, p_back), (d_agent, d_back) = await _boot_roles(
            h, params, ecfg, ["prefill", "decode"]
        )
        try:
            prompt = list(range(50, 70))  # 2 full pages + tail at ps 8
            # reference: plain single-node prefill+decode on the same
            # weights (direct backend call — no roles, no gateway)
            ref = await p_back.generate(tokens=prompt, max_new_tokens=6)

            doc = await _gen(
                h, "node-0.generate", {"tokens": prompt, "max_new_tokens": 6}
            )
            assert doc["result"]["tokens"] == ref["tokens"]
            assert doc["result"]["finish_reason"] == "length"

            assert p_back.engine.stats["kv_handoff_initiated_total"] == 1
            assert p_back.engine.stats["kv_handoff_bytes_total"] > 0
            assert d_back.engine.stats["kv_handoff_completed_total"] == 1
            # zero prefill on the decode side: the live install skipped it
            assert d_back.engine.stats["prefill_tokens"] == 0
            assert (
                h.cp.metrics.counter_value("gateway_handoff_fallback_total") == 0
            )

            # ONE waterfall across both nodes
            eid = doc["execution_id"]
            async with h.http.get(f"/api/v1/executions/{eid}/trace") as r:
                tr = await r.json()
            names = [s["name"] for s in tr["spans"]]
            assert "gateway.handoff" in names
            assert "engine.kv_export" in names

            # stats -> heartbeat -> /metrics: always-present counters
            for agent, nid in ((p_agent, "node-0"), (d_agent, "node-1")):
                await h.cp.registry.heartbeat(
                    nid, {"stats": agent.heartbeat_stats()}
                )
            for k in HANDOFF_COUNTERS:
                v = h.cp.metrics.gauge_value(
                    f"engine_{k}", labels={"node": "node-0"}
                )
                assert v is not None
            # per-role node-count gauge from the registry sweep
            await h.cp.registry.sweep_once()
            for role, n in (("prefill", 1.0), ("decode", 1.0), ("mixed", 0.0)):
                assert h.cp.metrics.gauge_value(
                    "nodes_by_role", labels={"role": role}
                ) == n

            await _assert_drained_zero_leak(p_back, d_back)
        finally:
            await _stop_nodes((p_agent, p_back), (d_agent, d_back))


@async_test
async def test_mixed_fleet_never_enters_two_phase():
    """Default-role fleets are bit-compatible with pre-pools dispatch: no
    phase state, no handoff counters, no handoff spans."""
    _cfg, params, ecfg = _boot_pair()
    async with CPHarness() as h:
        (a_agent, a_back), (b_agent, b_back) = await _boot_roles(
            h, params, ecfg, ["mixed", "mixed"]
        )
        try:
            doc = await _gen(
                h, "node-0.generate",
                {"tokens": list(range(30, 48)), "max_new_tokens": 4},
            )
            assert len(doc["result"]["tokens"]) == 4
            for back in (a_back, b_back):
                for k in HANDOFF_COUNTERS:
                    assert back.engine.stats[k] == 0
            assert h.cp.gateway._handoff == {}
            assert (
                h.cp.metrics.counter_value("gateway_handoff_fallback_total") == 0
            )
            await _assert_drained_zero_leak(a_back, b_back)
        finally:
            await _stop_nodes((a_agent, a_back), (b_agent, b_back))


@async_test
async def test_handoff_fail_chaos_single_node_token_exact_zero_leak():
    """Seeded kv.handoff_fail vetoes the export at decision time: the
    prefill node decodes the whole request itself (single-node degradation),
    token-exact, zero leaked pages on both nodes."""
    _cfg, params, ecfg = _boot_pair()
    async with CPHarness() as h:
        (p_agent, p_back), (d_agent, d_back) = await _boot_roles(
            h, params, ecfg, ["prefill", "decode"]
        )
        try:
            prompt = list(range(90, 112))
            ref = await p_back.generate(tokens=prompt, max_new_tokens=6)

            faults.install(
                faults.FaultInjector(seed=5, spec={"kv.handoff_fail": {"times": 1}})
            )
            try:
                doc = await _gen(
                    h, "node-0.generate", {"tokens": prompt, "max_new_tokens": 6}
                )
            finally:
                faults.install(None)
            assert doc["result"]["tokens"] == ref["tokens"]
            # the decline was counted on the prefill node; nothing ever
            # reached the decode node
            assert p_back.engine.stats["kv_handoff_failed_total"] == 1
            assert p_back.engine.stats["kv_handoff_initiated_total"] == 0
            assert d_back.engine.stats["kv_handoff_completed_total"] == 0
            assert d_back.engine.stats["requests_finished"] == 0
            # the gateway saw a phase-1 terminal without a handoff: counted
            # fallback, completed with the full single-node result
            assert (
                h.cp.metrics.counter_value("gateway_handoff_fallback_total") == 1
            )
            await _assert_drained_zero_leak(p_back, d_back)
        finally:
            await _stop_nodes((p_agent, p_back), (d_agent, d_back))


@async_test
async def test_handoff_stall_chaos_decode_reprefills_token_exact_zero_leak():
    """Seeded kv.handoff_stall outlives the decode node's fetch timeout:
    phase 2 adopts nothing and re-prefills the whole prompt locally —
    still single-node execution of the full request, token-exact under
    greedy (the first token re-samples identically), zero leaked pages on
    both nodes, and the stranded tail stash expires instead of leaking."""
    _cfg, params, ecfg = _boot_pair()
    async with CPHarness() as h:
        (p_agent, p_back), (d_agent, d_back) = await _boot_roles(
            h, params, ecfg, ["prefill", "decode"]
        )
        try:
            prompt = list(range(130, 154))
            ref = await p_back.generate(tokens=prompt, max_new_tokens=6)

            d_back.kv_fetch_timeout_s = 0.15
            faults.install(
                faults.FaultInjector(
                    seed=6,
                    spec={"kv.handoff_stall": {"times": 1, "delay_s": 1.0}},
                )
            )
            try:
                pre = d_back.engine.stats["prefill_tokens"]
                doc = await _gen(
                    h, "node-0.generate", {"tokens": prompt, "max_new_tokens": 6}
                )
            finally:
                faults.install(None)
            assert doc["result"]["tokens"] == ref["tokens"]
            assert p_back.engine.stats["kv_handoff_initiated_total"] == 1
            assert d_back.engine.stats["kv_fetch_failed_total"] == 1
            assert d_back.engine.stats["kv_handoff_completed_total"] == 0
            # full local re-prefill on the decode node (nothing adopted)
            assert d_back.engine.stats["prefill_tokens"] - pre == len(prompt)
            # let the stalled serve task finish so its late frames are
            # provably discarded (the waiter is gone)
            await asyncio.sleep(1.0)
            assert d_back.engine.stats["kv_fetch_pages_adopted_total"] == 0
            await _assert_drained_zero_leak(p_back, d_back)
        finally:
            await _stop_nodes((p_agent, p_back), (d_agent, d_back))
